"""TCP store primitives, object collectives, and LinearBarrier semantics.

Structural model: reference tests/test_dist_store.py:57-194 (TCPStore +
LinearBarrier incl. timeout and error propagation).
"""

import threading
import time

import pytest

from torchsnapshot_tpu.dist_store import (
    BarrierError,
    InProcessStore,
    LinearBarrier,
    StoreTimeoutError,
    TCPStore,
)
from torchsnapshot_tpu.pg_wrapper import PGWrapper
from torchsnapshot_tpu.test_utils import ProcessGroup, get_free_port, multiprocess_test


def test_tcp_store_primitives() -> None:
    port = get_free_port()
    server = TCPStore("127.0.0.1", port, is_server=True)
    client = TCPStore("127.0.0.1", server.port, is_server=False)
    try:
        server.set("k", b"v")
        assert client.try_get("k") == b"v"
        assert client.try_get("missing") is None
        assert client.add("ctr", 3) == 3
        assert server.add("ctr", 2) == 5
        client.delete("k")
        assert server.try_get("k") is None
        with pytest.raises(StoreTimeoutError):
            client.get("never", timeout=0.2)
    finally:
        client.close()
        server.close()


def test_store_collectives_threads() -> None:
    """Exercise exchange/broadcast/scatter/barrier with threads sharing one
    in-process store."""
    store = InProcessStore()
    world = 3
    results = {}

    def worker(rank: int) -> None:
        pg = PGWrapper(ProcessGroup(store=store, rank=rank, world_size=world))
        results[(rank, "ag")] = pg.all_gather_object(f"obj{rank}")
        results[(rank, "bc")] = pg.broadcast_object(
            "from0" if rank == 0 else None
        )
        results[(rank, "sc")] = pg.scatter_object_list(
            [f"to{i}" for i in range(world)] if rank == 0 else None
        )
        pg.barrier()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for r in range(world):
        assert results[(r, "ag")] == ["obj0", "obj1", "obj2"]
        assert results[(r, "bc")] == "from0"
        assert results[(r, "sc")] == f"to{r}"
    # Collective keys are transient: nothing should linger.
    assert store._kv == {}


def test_gather_object_to_leader_threads() -> None:
    """gather: dst receives rank-ordered blobs, others receive None, the
    dst's own blob never touches the store, and keys are cleaned up."""
    store = InProcessStore()
    world = 3
    results = {}
    set_keys = []
    orig_set = store.set

    def spying_set(key, value):
        set_keys.append(key)
        orig_set(key, value)

    store.set = spying_set

    def worker(rank: int) -> None:
        pg = PGWrapper(ProcessGroup(store=store, rank=rank, world_size=world))
        results[rank] = pg.gather_object({"rank": rank})

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results[0] == [{"rank": 0}, {"rank": 1}, {"rank": 2}]
    assert results[1] is None and results[2] is None
    assert store._kv == {}  # transient keys cleaned
    # Only non-destination ranks published blobs (suffixes /1 and /2).
    gather_sets = [k for k in set_keys if "/ga/" in k]
    assert sorted(k.rsplit("/", 1)[1] for k in gather_sets) == ["1", "2"]


class _FlakyStore(InProcessStore):
    """Raises on the first ``fail_first_n`` reads, then recovers."""

    def __init__(self, fail_first_n: int) -> None:
        super().__init__()
        self.fails_left = fail_first_n
        self.raised = 0

    def try_get(self, key):
        if self.fails_left > 0:
            self.fails_left -= 1
            self.raised += 1
            raise ConnectionError("simulated transport hiccup")
        return super().try_get(key)


class _DeadStore(InProcessStore):
    def try_get(self, key):
        raise ConnectionError("store is gone")


def test_get_rides_out_transient_read_failures() -> None:
    """try_get raising means "could not observe", not "absent"; the
    deadline-bounded helpers retry through brief failures."""
    store = _FlakyStore(fail_first_n=3)
    store.set("k", b"v")
    assert store.get("k", timeout=5.0) == b"v"
    assert store.raised == 3


def test_get_reraises_on_persistently_dead_store() -> None:
    """A store failing continuously must re-raise after the short grace,
    not be polled until the full deadline (a dead TCPStore socket means
    the leader is gone)."""
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        _DeadStore().get("k", timeout=60.0)
    assert time.monotonic() - t0 < 30.0  # grace, not the 60s deadline


def test_barrier_tolerates_transient_read_failures() -> None:
    """A momentary store error inside a barrier wait must not abort the
    commit barrier."""
    store = _FlakyStore(fail_first_n=2)
    world = 2
    errors = []

    def worker(rank: int) -> None:
        try:
            b = LinearBarrier("b", store, rank=rank, world_size=world)
            b.arrive(timeout=30.0)
            b.depart(timeout=30.0)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert store.raised == 2  # the hiccups actually happened


def test_linear_barrier_happy_path() -> None:
    store = InProcessStore()
    world = 3
    order = []

    def worker(rank: int) -> None:
        b = LinearBarrier("test", store, rank, world)
        b.arrive(timeout=10)
        order.append(rank)
        b.depart(timeout=10)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(order) == [0, 1, 2]
    assert store._kv == {}  # cleaned up after depart


def test_linear_barrier_error_propagation() -> None:
    """A peer's report_error poisons every other rank's wait — no rank may
    proceed to commit (reference dist_store.py:177-193)."""
    store = InProcessStore()
    world = 2
    caught = {}

    def rank0() -> None:
        b = LinearBarrier("err", store, 0, world)
        try:
            b.arrive(timeout=10)
        except BarrierError as e:
            caught[0] = e

    def rank1() -> None:
        b = LinearBarrier("err", store, 1, world)
        time.sleep(0.05)
        b.report_error(RuntimeError("injected rank-1 failure"))

    threads = [threading.Thread(target=rank0), threading.Thread(target=rank1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert 0 in caught
    assert "injected rank-1 failure" in repr(caught[0].__cause__)


def test_linear_barrier_timeout() -> None:
    store = InProcessStore()
    b = LinearBarrier("t", store, 0, 2)  # peer never arrives
    with pytest.raises(StoreTimeoutError):
        b.arrive(timeout=0.2)


def test_barrier_depart_requires_arrive() -> None:
    b = LinearBarrier("x", InProcessStore(), 0, 1)
    with pytest.raises(RuntimeError, match="before arrive"):
        b.depart()


@multiprocess_test(nproc=2)
def test_collectives_across_processes(pg) -> None:
    wrapper = PGWrapper(pg)
    gathered = wrapper.all_gather_object({"rank": pg.rank})
    assert gathered == [{"rank": 0}, {"rank": 1}]
    assert wrapper.broadcast_object("x" if pg.rank == 0 else None) == "x"
    wrapper.barrier()


def test_world_32_stress_over_tcp() -> None:
    """Scale check for the coordination layer (VERDICT r1 item 4): 32 ranks
    — each with its own TCP client connection — run LinearBarrier
    arrive/depart, a manifest-sized exchange, and a counter barrier, and
    the whole thing completes in seconds. The leader's waits are single
    counter-key polls and exchange is a rank-0 aggregate + one fetch per
    rank, so wall time stays flat-ish in world size."""
    world = 32
    server = TCPStore("127.0.0.1", 0, is_server=True)
    payload = {"manifest": ["0/model/layer/%d" % i for i in range(200)]}
    results: dict = {}
    errors: list = []

    def worker(rank: int) -> None:
        client = (
            server
            if rank == 0
            else TCPStore("127.0.0.1", server.port, is_server=False)
        )
        try:
            pg = PGWrapper(
                ProcessGroup(store=client, rank=rank, world_size=world)
            )
            gathered = pg.all_gather_object({**payload, "rank": rank})
            assert [g["rank"] for g in gathered] == list(range(world))
            barrier = LinearBarrier(
                "stress32", client, rank=rank, world_size=world
            )
            barrier.arrive(timeout=60)
            barrier.depart(timeout=60)
            pg.barrier()
            results[rank] = True
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))
        finally:
            if rank != 0:
                client.close()

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(r,)) for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    elapsed = time.monotonic() - t0
    server.close()
    assert not errors, errors[:3]
    assert len(results) == world
    assert elapsed < 60, f"world-32 coordination took {elapsed:.1f}s"


def test_jax_pg_fallback_bootstraps_tcp_store() -> None:
    """A coordination client without atomic increment must get a TCPStore
    bootstrapped through set/get (the two primitives every KV has) instead
    of NotImplementedError surfacing mid-collective."""
    from torchsnapshot_tpu.dist_store import _bootstrap_tcp_store

    kv = InProcessStore()  # stands in for the coordination KV (set/get only)
    stores = {}

    def worker(rank: int) -> None:
        stores[rank] = _bootstrap_tcp_store(kv, rank, timeout=30)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert sorted(stores) == [0, 1, 2]
    try:
        stores[0].set("k", b"v")
        assert stores[1].try_get("k") == b"v"
        assert stores[2].add("c", 5) == 5
        assert stores[1].add("c", 1) == 6
    finally:
        for s in stores.values():
            s.close()


def test_world_32_snapshot_take_restore(tmp_path) -> None:
    """Full Snapshot.take + restore at world 32 over one TCP store: the
    manifest gather (rank-0 aggregate exchange), replicated verification,
    partitioning, commit barrier — every coordination round at a pod-ish
    world size, in seconds."""
    import numpy as np

    import torchsnapshot_tpu as ts

    world = 32
    server = TCPStore("127.0.0.1", 0, is_server=True)
    path = str(tmp_path / "snap")
    errors: list = []

    def worker(rank: int) -> None:
        client = (
            server
            if rank == 0
            else TCPStore("127.0.0.1", server.port, is_server=False)
        )
        try:
            pg = ProcessGroup(store=client, rank=rank, world_size=world)
            state = {"w": np.full((64,), float(rank), np.float32), "r": rank}
            ts.Snapshot.take(path, {"s": ts.PyTreeState(state)}, pg=pg)
            dst = {"w": np.zeros((64,), np.float32), "r": -1}
            wrapped = ts.PyTreeState(dst)
            ts.Snapshot(path, pg=pg).restore({"s": wrapped})
            np.testing.assert_array_equal(
                wrapped.tree["w"], np.full((64,), float(rank), np.float32)
            )
            assert wrapped.tree["r"] == rank
        except Exception as e:  # noqa: BLE001
            errors.append((rank, repr(e)))
        finally:
            if rank != 0:
                client.close()

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(r,)) for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    elapsed = time.monotonic() - t0
    server.close()
    assert not errors, errors[:3]
    assert elapsed < 120, f"world-32 take+restore took {elapsed:.1f}s"


def test_jax_process_group_is_cached(monkeypatch) -> None:
    """Repeated jax_process_group() calls must return the same ProcessGroup
    (same store object): op-seq namespaces stay shared, and the TCPStore
    fallback never bootstraps a second server under the same address key."""
    import torchsnapshot_tpu.dist_store as ds

    monkeypatch.setattr(ds, "_JAX_PG", None)
    sentinel_store = InProcessStore()
    monkeypatch.setattr(ds, "JaxCoordinationStore", lambda: sentinel_store)
    monkeypatch.setattr(
        ds.InProcessStore, "supports_add", lambda self: True, raising=False
    )
    pg1 = ds.jax_process_group()
    pg2 = ds.jax_process_group()
    assert pg1 is pg2
    assert pg1.store is sentinel_store
    monkeypatch.setattr(ds, "_JAX_PG", None)


def test_tcp_store_connect_timeout_is_a_clear_error() -> None:
    """A client whose rank-0 store server never comes up must fail with
    a deadline-bounded StoreTimeoutError naming the address — not a raw
    ECONNREFUSED escaping from deep inside a collective (snaplint
    satellite: every dist_store poll loop is deadline-bounded with a
    clear timeout error)."""
    port = get_free_port()  # freed immediately: nothing listens on it
    client = TCPStore(
        "127.0.0.1", port, is_server=False, connect_timeout=0.3
    )
    t0 = time.monotonic()
    with pytest.raises(StoreTimeoutError, match="Timed out connecting"):
        client.try_get("anything")
    assert time.monotonic() - t0 < 10.0
