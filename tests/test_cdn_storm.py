"""CDN subscriber storm (bench leg 11's harness) + the staleness
doctor rule: convergence, the ~1x read-amplification pin, the
rolling-update dedup pin, and warmup exclusion from the staleness
distribution."""

import os

from torchsnapshot_tpu.scalemodel import (
    CdnStormConfig,
    build_step_chunks,
    run_cdn_storm,
)
from torchsnapshot_tpu.telemetry import doctor, names


def test_schedule_is_deterministic_with_churn():
    cfg = CdnStormConfig(
        fleet_size=4, steps=3, chunks_per_step=8, churn_fraction=0.25
    )
    schedule, blobs = build_step_chunks(cfg)
    again, _ = build_step_chunks(cfg)
    assert schedule == again
    assert len(schedule) == cfg.warmup_steps + cfg.steps
    # Step 0 is all-new; later steps churn exactly 2 of 8 chunks.
    assert len(schedule[0]) == 8
    for prev, cur in zip(schedule, schedule[1:]):
        assert len(set(cur) - set(prev)) == 2
    for key, data in blobs.items():
        assert len(data) == cfg.chunk_bytes


def test_storm_converges_at_one_x_amplification():
    r = run_cdn_storm(
        CdnStormConfig(
            fleet_size=6,
            steps=2,
            chunks_per_step=6,
            chunk_bytes=2048,
            timeout_s=60.0,
        )
    )
    assert r.converged(), (r.converged_subscribers, r.errors)
    assert not r.errors
    # The pin: each unique chunk left durable storage exactly once,
    # regardless of fleet size.
    assert r.durable_reads == r.unique_chunks_published
    assert r.read_amplification == 1.0
    # Rolling update shipped only churned chunks: fleet wire bytes are
    # well under the fleet's logical step bytes.
    assert 0.0 < r.dedup_ratio < 1.0
    assert r.bytes_on_wire < r.bytes_in_steps
    assert r.peer_fallbacks == 0
    # Staleness covers measured (post-warmup) steps for every sub.
    assert r.staleness_samples == 6 * 2
    assert r.staleness_max_s >= r.staleness_median_s >= 0.0


def test_storm_without_swapper_still_tracks():
    r = run_cdn_storm(
        CdnStormConfig(
            fleet_size=3,
            steps=1,
            chunks_per_step=4,
            chunk_bytes=1024,
            swap=False,
            timeout_s=30.0,
        )
    )
    assert r.converged() and not r.errors
    assert r.read_amplification == 1.0


def test_storm_restores_pull_timeout_env():
    prior = os.environ.get("TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS")
    run_cdn_storm(
        CdnStormConfig(
            fleet_size=2,
            steps=1,
            chunks_per_step=2,
            chunk_bytes=512,
            timeout_s=30.0,
        )
    )
    assert (
        os.environ.get("TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS")
        == prior
    )


# ---------------------------------------------------------------------------
# cdn-staleness-high doctor rule
# ---------------------------------------------------------------------------


def _swap_record(staleness, seq=1, sub=0):
    return {
        "event": names.EVENT_CDN_SWAPPED,
        "topic": "t",
        "seq": seq,
        "step": seq,
        "subscriber": sub,
        "staleness_s": staleness,
    }


def _verdicts(records):
    ev = doctor.Evidence(
        path="x",
        ledger_records=records,
        ledger_file="/run/.ledger.jsonl",
    )
    return [
        v
        for v in doctor.diagnose_evidence(ev)
        if v.rule == names.RULE_CDN_STALENESS_HIGH
    ]


def test_staleness_rule_fires_over_budget(monkeypatch):
    monkeypatch.setenv(
        "TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS", "1.0"
    )
    records = [{"event": names.EVENT_CDN_PUBLISHED, "seq": 1}]
    records += [_swap_record(5.0, sub=i) for i in range(6)]
    verdicts = _verdicts(records)
    assert len(verdicts) == 1
    ev = verdicts[0].evidence
    assert ev["median_staleness_s"] == 5.0
    assert ev["budget_s"] == 1.0
    assert ev["swaps_observed"] == 6
    assert ev["publishes_observed"] == 1
    assert verdicts[0].source == ".ledger.jsonl"


def test_staleness_rule_quiet_within_budget(monkeypatch):
    monkeypatch.setenv(
        "TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS", "1.0"
    )
    assert _verdicts([_swap_record(0.2, sub=i) for i in range(6)]) == []


def test_staleness_rule_needs_min_samples(monkeypatch):
    monkeypatch.setenv(
        "TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS", "1.0"
    )
    # 4 samples < the 5-sample floor: one slow swap is an anecdote.
    assert _verdicts([_swap_record(9.0, sub=i) for i in range(4)]) == []


def test_staleness_rule_disabled_by_zero_budget(monkeypatch):
    monkeypatch.setenv(
        "TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS", "0"
    )
    assert _verdicts([_swap_record(9.0, sub=i) for i in range(8)]) == []


def test_staleness_rule_end_to_end_through_a_real_ledger(tmp_path):
    """Post real ledger events through the subscriber's path (root=),
    then diagnose the directory like the CLI would."""
    from torchsnapshot_tpu import knobs
    from torchsnapshot_tpu.telemetry import ledger

    root = str(tmp_path)
    with knobs.enable_ledger():
        ledger.open_run(root)
        for i in range(6):
            ledger.post_event(
                root,
                names.EVENT_CDN_SWAPPED,
                topic="t",
                seq=1,
                step=1,
                subscriber=i,
                staleness_s=9.5,
            )
        os.environ[
            "TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS"
        ] = "1.0"
        try:
            ev = doctor.gather_evidence(root)
            verdicts = [
                v
                for v in doctor.diagnose_evidence(ev)
                if v.rule == names.RULE_CDN_STALENESS_HIGH
            ]
        finally:
            os.environ.pop(
                "TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS", None
            )
    assert len(verdicts) == 1
    assert verdicts[0].evidence["median_staleness_s"] == 9.5
