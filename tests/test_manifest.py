"""Manifest schema: YAML/JSON round-trips and per-rank availability rules.

Structural model: reference tests/test_manifest.py:244-331.
"""

import copy

import pytest

from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    DictEntry,
    ListEntry,
    ObjectEntry,
    OrderedDictEntry,
    PrimitiveEntry,
    Shard,
    ShardedArrayEntry,
    SnapshotMetadata,
    get_manifest_for_rank,
    is_container_entry,
    is_replicated,
)


def _array(location: str, replicated: bool = False, byte_range=None) -> ArrayEntry:
    return ArrayEntry(
        location=location,
        serializer="buffer_protocol",
        dtype="float32",
        shape=[4, 4],
        replicated=replicated,
        byte_range=byte_range,
    )


def _sample_metadata() -> SnapshotMetadata:
    manifest = {
        "0/model": DictEntry(keys=["weight", "bias", "stats", "step", "lr", "name"]),
        "0/model/weight": _array("replicated/model/weight", replicated=True),
        "0/model/bias": _array("0/model/bias"),
        "0/model/stats": ObjectEntry(
            location="0/model/stats",
            serializer="pickle",
            obj_type="dict",
            replicated=False,
        ),
        "0/model/step": PrimitiveEntry.from_object(123),
        "0/model/lr": PrimitiveEntry.from_object(0.1),
        "0/model/name": PrimitiveEntry.from_object("net"),
        "0/sharded": ShardedArrayEntry(
            dtype="float32",
            shape=[8, 4],
            shards=[Shard(offsets=[0, 0], sizes=[4, 4], array=_array("sharded/s/0"))],
        ),
        "0/big": ChunkedArrayEntry(
            dtype="float32",
            shape=[8, 4],
            chunks=[
                Shard(offsets=[0, 0], sizes=[4, 4], array=_array("0/big/chunk_0")),
                Shard(offsets=[4, 0], sizes=[4, 4], array=_array("0/big/chunk_1")),
            ],
            replicated=False,
        ),
        "0/misc": ListEntry(),
        "0/misc/0": PrimitiveEntry.from_object(True),
        "0/od": OrderedDictEntry(keys=["k"]),
        "0/od/k": PrimitiveEntry.from_object(b"\x00\x01"),
        "1/model": DictEntry(keys=["weight", "bias"]),
        "1/model/weight": _array("replicated/model/weight", replicated=True),
        "1/model/bias": _array("1/model/bias"),
        "1/sharded": ShardedArrayEntry(
            dtype="float32",
            shape=[8, 4],
            shards=[Shard(offsets=[4, 0], sizes=[4, 4], array=_array("sharded/s/1"))],
        ),
    }
    return SnapshotMetadata(version="0.1.0", world_size=2, manifest=manifest)


def test_yaml_roundtrip() -> None:
    md = _sample_metadata()
    restored = SnapshotMetadata.from_yaml(md.to_yaml())
    assert restored == md


def test_json_stays_yaml_loadable() -> None:
    """The huge-manifest escape hatch: JSON-emitted metadata must load
    through the YAML path (reference invariant: tests/test_manifest.py:259-281).
    """
    md = _sample_metadata()
    restored = SnapshotMetadata.from_yaml(md.to_json())
    assert restored == md


def test_primitive_values_exact() -> None:
    for value in [0, -17, True, False, "str", b"\xff\x00", 0.1, 1e-300, -0.0]:
        entry = PrimitiveEntry.from_object(value)
        out = entry.get_value()
        assert type(out) is type(value)
        assert out == value or (value != value and out != out)
    # float exactness through serialization
    e = PrimitiveEntry.from_object(0.1)
    restored = SnapshotMetadata(
        version="0", world_size=1, manifest={"0/x": e}
    ).to_yaml()
    md = SnapshotMetadata.from_yaml(restored)
    assert md.manifest["0/x"].get_value() == 0.1


def test_unknown_entry_type_raises() -> None:
    md_yaml = _sample_metadata().to_yaml().replace("type: Array", "type: Cube", 1)
    with pytest.raises(ValueError):
        SnapshotMetadata.from_yaml(md_yaml)


def test_get_manifest_for_rank_rules() -> None:
    md = _sample_metadata()
    m0 = get_manifest_for_rank(md, 0)
    m1 = get_manifest_for_rank(md, 1)

    # Per-rank entries stay with their owner.
    assert "model/bias" in m0 and m0["model/bias"].location == "0/model/bias"
    assert "model/bias" in m1 and m1["model/bias"].location == "1/model/bias"
    assert "big" in m0 and "big" not in m1
    assert "misc" in m0 and "misc" not in m1

    # Replicated entries are available everywhere.
    assert m0["model/weight"].replicated and m1["model/weight"].replicated

    # Sharded entries merge across ranks and are available everywhere.
    for m in (m0, m1):
        assert [s.offsets for s in m["sharded"].shards] == [[0, 0], [4, 0]]


def test_get_manifest_for_rank_beyond_world_size() -> None:
    """An elastic-restore rank > world_size still sees replicated + sharded
    entries (with container chains), just not per-rank state."""
    md = _sample_metadata()
    m5 = get_manifest_for_rank(md, 5)
    assert "model/weight" in m5
    assert "model" in m5  # ancestor container grafted
    assert "model/bias" not in m5
    assert [s.offsets for s in m5["sharded"].shards] == [[0, 0], [4, 0]]


def test_graft_does_not_mutate_global_manifest() -> None:
    md = _sample_metadata()
    before = copy.deepcopy(md)
    get_manifest_for_rank(md, 5)
    get_manifest_for_rank(md, 1)
    assert md == before


def test_helpers() -> None:
    assert is_container_entry(ListEntry())
    assert is_container_entry(DictEntry(keys=[]))
    assert not is_container_entry(_array("x"))
    assert is_replicated(_array("x", replicated=True))
    assert not is_replicated(ListEntry())


def test_byte_range_tuple() -> None:
    assert _array("x").byte_range_tuple is None
    assert _array("x", byte_range=[3, 9]).byte_range_tuple == (3, 9)


def test_graft_preserves_int_dict_keys() -> None:
    """Regression: grafted per-rank manifests must keep int dict keys int
    (review finding)."""
    from torchsnapshot_tpu.manifest import SnapshotMetadata

    md = SnapshotMetadata(
        version="0",
        world_size=2,
        manifest={
            "0/layers": DictEntry(keys=[0]),
            "0/layers/0": _array("replicated/layers/0", replicated=True),
        },
    )
    m1 = get_manifest_for_rank(md, 1)
    assert m1["layers"].keys == [0]
