"""Live-progress heartbeats: file atomicity under a concurrent reader,
lifecycle (removed on success, terminal on failure, leftover on crash),
and the always-on in-memory ``current_progress`` view mid-take.

Acceptance pin (ISSUE 5): during a take, a concurrent reader of the
per-rank progress file observes monotonically non-decreasing
bytes-written and a valid JSON document on every read; the file is
removed when the op completes; ``current_progress()`` is correct
mid-take via a slow fake plugin.
"""

import asyncio
import glob
import json
import os
import threading
import time

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import knobs, telemetry
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.telemetry import progress


def _state(n=8, size=4096, seed=0):
    rng = np.random.default_rng(seed)
    return {
        f"l{i}": rng.standard_normal(size).astype(np.float32)
        for i in range(n)
    }


def _slow_writes(monkeypatch, delay_s=0.03):
    """Inject per-blob write latency into the fs plugin so a take is
    slow enough for pollers to observe it mid-flight. The fused
    write+checksum fast path declines so every write takes the patched
    plain path."""
    orig = FSStoragePlugin.write

    async def slow_write(self, write_io):
        await asyncio.sleep(delay_s)
        await orig(self, write_io)

    async def decline_fused(self, write_io):
        return None

    monkeypatch.setattr(FSStoragePlugin, "write", slow_write)
    monkeypatch.setattr(
        FSStoragePlugin, "write_with_checksum", decline_fused
    )


def test_progress_path_resolution(tmp_path):
    """Interval <= 0 disables the file heartbeat; the dir knob takes
    precedence over the snapshot-adjacent file; object-store paths get
    no file without the dir knob; dir-mode names are disambiguated by
    snapshot-path digest and kind so concurrent ops on one rank never
    clobber (or finish()-delete) each other's heartbeats."""
    assert progress.progress_path_for(str(tmp_path), 0) is None  # conftest 0
    with knobs.override_progress_interval_seconds(0.5):
        assert progress.progress_path_for(str(tmp_path), 1) == str(
            tmp_path / ".progress-rank1.json"
        )
        assert progress.progress_path_for("s3://bucket/snap", 0) is None
        with knobs.override_progress_dir(str(tmp_path / "out")):
            assert progress.progress_path_for("s3://bucket/snap", 2) == str(
                tmp_path / "out" / "progress-rank2.json"
            )
            a = progress.progress_path_for(
                "s3://bucket/step_1", 0, kind="take"
            )
            b = progress.progress_path_for(
                "s3://bucket/step_2", 0, kind="take"
            )
            c = progress.progress_path_for(
                "s3://bucket/step_1", 0, kind="async_take"
            )
            assert len({a, b, c}) == 3


def test_dir_mode_findings_filter_by_snapshot_path(tmp_path):
    """A shared progress dir serves several snapshots; discovery for
    snapshot A must not return snapshot B's heartbeats (filtered by the
    path digest embedded in every dir-mode filename — one glob, no
    per-file parse)."""
    out = tmp_path / "out"
    out.mkdir()
    dig_a = progress._path_digest("s3://bucket/a")
    dig_b = progress._path_digest("s3://bucket/b")
    (out / f"progress-{dig_a}-take-rank0.json").write_text(
        json.dumps({"kind": "take", "path": "s3://bucket/a", "terminal": None})
    )
    (out / f"progress-{dig_b}-take-rank0.json").write_text(
        json.dumps({"kind": "take", "path": "s3://bucket/b", "terminal": None})
    )
    with knobs.override_progress_dir(str(out)):
        found = progress.find_progress_files("s3://bucket/a")
    assert [os.path.basename(f) for f in found] == [
        f"progress-{dig_a}-take-rank0.json"
    ]


def test_concurrent_reader_sees_valid_monotonic_heartbeats(
    tmp_path, monkeypatch
):
    """The acceptance pin: every concurrent read parses, written_bytes
    never decreases, and the file is gone once the take completes."""
    _slow_writes(monkeypatch)
    snap = str(tmp_path / "snap")
    heartbeat = os.path.join(snap, ".progress-rank0.json")
    docs = []
    raw_failures = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                with open(heartbeat, "r", encoding="utf-8") as f:
                    raw = f.read()
            except OSError:
                time.sleep(0.001)
                continue
            try:
                docs.append(json.loads(raw))
            except ValueError:
                raw_failures.append(raw)
            time.sleep(0.001)

    t = threading.Thread(target=reader)
    t.start()
    try:
        with knobs.override_progress_interval_seconds(0.001):
            ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state())})
    finally:
        stop.set()
        t.join()
    assert not raw_failures, f"torn reads: {raw_failures[:3]}"
    assert docs, "reader never saw a heartbeat"
    written = [d["written_bytes"] for d in docs]
    assert written == sorted(written), "written_bytes regressed"
    assert all(d["kind"] == "take" for d in docs)
    assert all(d["schema_version"] == progress.PROGRESS_SCHEMA_VERSION
               for d in docs)
    # Lifecycle: a completed op removes its heartbeat.
    assert not os.path.exists(heartbeat)
    planned = docs[-1]["planned_bytes"]
    assert planned == sum(a.nbytes for a in _state().values())
    assert written[-1] <= planned


def test_current_progress_mid_take(tmp_path, monkeypatch):
    """The always-on in-memory view (no file knobs at all): a poller
    thread sees the live take with sane, growing counters."""
    _slow_writes(monkeypatch)
    snap = str(tmp_path / "snap")
    rows = []
    stop = threading.Event()

    def poller():
        while not stop.is_set():
            rows.extend(telemetry.current_progress())
            time.sleep(0.002)

    t = threading.Thread(target=poller)
    t.start()
    try:
        ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state())})
    finally:
        stop.set()
        t.join()
    takes = [r for r in rows if r["kind"] == "take"]
    assert takes, "current_progress never showed the live take"
    assert takes[0]["path"] == snap
    assert takes[0]["rank"] == 0
    written = [r["written_bytes"] for r in takes]
    assert written == sorted(written)
    planned = sum(a.nbytes for a in _state().values())
    assert any(r["planned_bytes"] == planned for r in takes)
    assert any(r["phase"] in ("staging", "writing") for r in takes)
    # No file heartbeat was requested (conftest interval 0): nothing on
    # disk, and the op unregistered at completion.
    assert not glob.glob(os.path.join(snap, ".progress*"))
    assert telemetry.current_progress() == []


def test_heartbeat_refreshes_while_write_is_blocked(tmp_path, monkeypatch):
    """A blocked op produces no pipeline events, but the heartbeat must
    keep refreshing (background refresher): updated_unix_ts advances
    with written_bytes frozen — 'alive but stuck', not 'crashed'. This
    is what keeps the doctor's staleness-based interrupted-take check
    honest for single-blob multi-minute writes."""
    _slow_writes(monkeypatch, delay_s=0.6)
    snap = str(tmp_path / "snap")
    heartbeat = os.path.join(snap, ".progress-rank0.json")
    stamps = set()
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            doc = progress.load_progress_file(heartbeat)
            if doc is not None and doc["written_bytes"] == 0:
                stamps.add(doc["updated_unix_ts"])
            time.sleep(0.01)

    t = threading.Thread(target=reader)
    t.start()
    try:
        with knobs.override_progress_interval_seconds(0.05):
            ts.Snapshot.take(
                snap, {"s": ts.PyTreeState(_state(n=1, size=256))}
            )
    finally:
        stop.set()
        t.join()
    # The single write blocks ~0.6s with zero pipeline events; without
    # the refresher at most two stamps exist (registration + staging).
    assert len(stamps) >= 4, stamps


def test_failed_take_leaves_terminal_heartbeat(tmp_path, monkeypatch):
    """A take whose writes fail must leave a TERMINAL heartbeat with
    the error — distinguishing a clean failure from a crash's
    non-terminal leftover — and unregister from current_progress."""

    async def broken_write(self, write_io):
        raise OSError("injected disk failure")

    monkeypatch.setattr(FSStoragePlugin, "write", broken_write)
    monkeypatch.setattr(FSStoragePlugin, "write_with_checksum", broken_write)
    snap = str(tmp_path / "snap")
    with knobs.override_progress_interval_seconds(0.001):
        with pytest.raises(OSError):
            ts.Snapshot.take(snap, {"s": ts.PyTreeState(_state(n=2))})
    heartbeat = os.path.join(snap, ".progress-rank0.json")
    assert os.path.exists(heartbeat)
    doc = progress.load_progress_file(heartbeat)
    assert doc["terminal"] == "failed"
    assert "injected disk failure" in doc["error"]
    assert telemetry.current_progress() == []


def test_restore_progress_accumulates_across_pipelines(tmp_path):
    """A restore runs one read pipeline per stateful; the published
    totals must fold them (begin_pipeline offsets), ending at the full
    byte count."""
    snap = str(tmp_path / "snap")
    state_a, state_b = _state(n=2, seed=1), _state(n=3, seed=2)
    ts.Snapshot.take(
        snap, {"a": ts.PyTreeState(state_a), "b": ts.PyTreeState(state_b)}
    )
    tracker_rows = []
    orig_finish = progress.ProgressTracker.finish

    def spy_finish(self, error=None):
        tracker_rows.append(self.snapshot())
        orig_finish(self, error)

    try:
        progress.ProgressTracker.finish = spy_finish
        dest = {
            "a": ts.PyTreeState(
                {k: np.zeros_like(v) for k, v in state_a.items()}
            ),
            "b": ts.PyTreeState(
                {k: np.zeros_like(v) for k, v in state_b.items()}
            ),
        }
        ts.Snapshot(snap).restore(dest)
    finally:
        progress.ProgressTracker.finish = orig_finish
    restores = [r for r in tracker_rows if r["kind"] == "restore"]
    assert len(restores) == 1
    total = sum(a.nbytes for a in state_a.values()) + sum(
        a.nbytes for a in state_b.values()
    )
    assert restores[0]["planned_bytes"] == total
    assert restores[0]["written_bytes"] == total
    assert restores[0]["items_done"] == len(state_a) + len(state_b)


def test_async_take_heartbeat_settles_on_background_thread(
    tmp_path, monkeypatch
):
    """async_take's heartbeat stays live through the background drain
    and is removed when the commit thread settles."""
    _slow_writes(monkeypatch, delay_s=0.02)
    snap = str(tmp_path / "snap")
    with knobs.override_progress_interval_seconds(0.001):
        pending = ts.Snapshot.async_take(
            snap, {"s": ts.PyTreeState(_state(n=4))}
        )
        live = [
            r
            for r in telemetry.current_progress()
            if r["kind"] == "async_take"
        ]
        assert live and live[0]["path"] == snap
        pending.wait()
    assert not os.path.exists(os.path.join(snap, ".progress-rank0.json"))
    assert telemetry.current_progress() == []


def test_manager_gc_reaps_dir_mode_heartbeats(tmp_path):
    """Shared-dir heartbeats have no other reaper: dropping a step must
    remove its dir-mode leftovers (and only its own)."""
    out = tmp_path / "out"
    out.mkdir()
    snap_a = "s3://bucket/step_1"
    dig_a = progress._path_digest(snap_a)
    dig_b = progress._path_digest("s3://bucket/step_2")
    (out / f"progress-{dig_a}-take-rank0.json").write_text("{}")
    (out / f"progress-{dig_b}-take-rank0.json").write_text("{}")
    with knobs.override_progress_dir(str(out)):
        progress.remove_dir_heartbeats(snap_a)
    assert [p.name for p in sorted(out.iterdir())] == [
        f"progress-{dig_b}-take-rank0.json"
    ]


def test_find_and_load_progress_files(tmp_path):
    """fsck/doctor discovery: snapshot-adjacent leftovers are found and
    unreadable files load as None instead of raising."""
    snap = tmp_path / "snap"
    snap.mkdir()
    good = snap / ".progress-rank0.json"
    good.write_text(json.dumps({"kind": "take", "terminal": None}))
    bad = snap / ".progress-rank1.json"
    bad.write_text("{torn")
    files = progress.find_progress_files(str(snap))
    assert [os.path.basename(f) for f in files] == [
        ".progress-rank0.json",
        ".progress-rank1.json",
    ]
    assert progress.load_progress_file(str(good))["kind"] == "take"
    assert progress.load_progress_file(str(bad)) is None
