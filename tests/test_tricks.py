"""Framework integrations (tricks/): flax TrainState round-trip and orbax
migration in both directions — the analog of the reference's DeepSpeed
bridge coverage (tricks/deepspeed.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchsnapshot_tpu import PyTreeState, Snapshot


def _make_train_state(seed: int):
    flax = pytest.importorskip("flax")
    from flax.training.train_state import TrainState

    key = jax.random.PRNGKey(seed)
    params = {
        "dense": {
            "kernel": jax.random.normal(key, (4, 8), dtype=jnp.float32),
            "bias": jnp.zeros((8,), dtype=jnp.float32),
        }
    }
    tx = optax.adam(1e-3)
    return TrainState.create(
        apply_fn=lambda p, x: x @ p["dense"]["kernel"] + p["dense"]["bias"],
        params=params,
        tx=tx,
    )


def test_flax_train_state_roundtrip(tmp_path) -> None:
    from torchsnapshot_tpu.tricks.flax import TrainStateStateful

    state = _make_train_state(0)
    # Advance one step so opt_state moments are nonzero.
    grads = jax.tree_util.tree_map(jnp.ones_like, state.params)
    state = state.apply_gradients(grads=grads)

    Snapshot.take(str(tmp_path / "snap"), {"train": TrainStateStateful(state)})

    dest = TrainStateStateful(_make_train_state(1))
    Snapshot(str(tmp_path / "snap")).restore({"train": dest})

    assert int(dest.state.step) == int(state.step) == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(
            (state.params, state.opt_state, state.step)
        ),
        jax.tree_util.tree_leaves(
            (dest.state.params, dest.state.opt_state, dest.state.step)
        ),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Non-checkpointed fields survive from the destination state.
    assert dest.state.apply_fn is not None
    assert dest.state.tx is not None


def test_flax_stateful_rejects_non_train_state() -> None:
    from torchsnapshot_tpu.tricks.flax import TrainStateStateful

    with pytest.raises(TypeError, match="params"):
        TrainStateStateful({"just": "a dict"})


def test_orbax_roundtrip_both_directions(tmp_path) -> None:
    ocp = pytest.importorskip("orbax.checkpoint")
    from torchsnapshot_tpu.tricks.orbax import (
        load_orbax_pytree,
        migrate_orbax_to_snapshot,
        migrate_snapshot_to_orbax,
    )

    tree = {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "nested": {"b": np.ones((5,), dtype=np.int32)},
    }
    orbax_dir = str(tmp_path / "orbax_src")
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(orbax_dir, tree)

    # orbax → Snapshot
    snap_dir = str(tmp_path / "snap")
    migrate_orbax_to_snapshot(orbax_dir, snap_dir)
    dest = PyTreeState(jax.tree_util.tree_map(np.zeros_like, tree))
    Snapshot(snap_dir).restore({"state": dest})
    np.testing.assert_array_equal(dest.tree["w"], tree["w"])
    np.testing.assert_array_equal(dest.tree["nested"]["b"], tree["nested"]["b"])

    # Snapshot → orbax
    orbax_out = str(tmp_path / "orbax_out")
    restored = migrate_snapshot_to_orbax(
        snap_dir, orbax_out, item=jax.tree_util.tree_map(np.zeros_like, tree)
    )
    np.testing.assert_array_equal(restored["w"], tree["w"])
    back = load_orbax_pytree(orbax_out)
    np.testing.assert_array_equal(np.asarray(back["w"]), tree["w"])
    np.testing.assert_array_equal(
        np.asarray(back["nested"]["b"]), tree["nested"]["b"]
    )


def test_orbax_handler_checkpointer_roundtrip(tmp_path) -> None:
    """The deepspeed-trick analog: an existing orbax Checkpointer call site
    writes/reads THIS framework's format once the handler is swapped in."""
    ocp = pytest.importorskip("orbax.checkpoint")
    from torchsnapshot_tpu.tricks.orbax import (
        snapshot_checkpoint_handler,
        snapshot_restore_args,
        snapshot_save_args,
    )

    tree = {"params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}, "step": 42}
    path = str(tmp_path / "ckpt")
    ckptr = ocp.Checkpointer(snapshot_checkpoint_handler())
    ckptr.save(path, args=snapshot_save_args(tree))
    assert os.path.exists(os.path.join(path, ".snapshot_metadata"))

    raw = ckptr.restore(path)  # template-free (orbax raw semantics)
    np.testing.assert_array_equal(raw["params"]["w"], tree["params"]["w"])
    assert raw["step"] == 42

    tmpl = {"params": {"w": np.zeros((3, 4), np.float32)}, "step": 0}
    out = ckptr.restore(path, args=snapshot_restore_args(tmpl))
    np.testing.assert_array_equal(out["params"]["w"], tree["params"]["w"])
    assert out["step"] == 42
    ckptr.close()


def test_orbax_handler_checkpoint_manager_retention(tmp_path) -> None:
    """An EXISTING orbax CheckpointManager retention loop (max_to_keep)
    runs unchanged over the snapshot format."""
    ocp = pytest.importorskip("orbax.checkpoint")
    from torchsnapshot_tpu.tricks.orbax import (
        snapshot_checkpoint_handler,
        snapshot_save_args,
    )

    mgr = ocp.CheckpointManager(
        str(tmp_path),
        options=ocp.CheckpointManagerOptions(max_to_keep=2),
        item_handlers=snapshot_checkpoint_handler(),
    )
    for step in range(4):
        mgr.save(
            step,
            args=snapshot_save_args({"w": np.full((8,), float(step), np.float32)}),
        )
    mgr.wait_until_finished()
    assert mgr.all_steps() == [2, 3]
    out = mgr.restore(mgr.latest_step())
    np.testing.assert_array_equal(out["w"], np.full((8,), 3.0, np.float32))
    mgr.close()


def test_orbax_handler_key_mismatch_raises(tmp_path) -> None:
    ocp = pytest.importorskip("orbax.checkpoint")
    from torchsnapshot_tpu.tricks.orbax import (
        snapshot_checkpoint_handler,
        snapshot_save_args,
    )

    path = str(tmp_path / "c")
    ckptr = ocp.Checkpointer(snapshot_checkpoint_handler(key="state"))
    ckptr.save(path, args=snapshot_save_args({"x": np.ones(2, np.float32)}))
    ckptr.close()
    other = ocp.Checkpointer(snapshot_checkpoint_handler(key="model"))
    with pytest.raises(ValueError, match="no app-state key"):
        other.restore(path)
    other.close()
