"""Zero-pack direct write path (native pwritev+CRC, O_DIRECT slabs).

Pins the PR's structural claims:

- the vectorized slab stage runs NO pack pass (no ``gather_memcpy``, no
  member scatter, no ``batcher:stage_slab`` span — the distinct
  ``batcher:stage_slab_vectorized`` span appears instead);
- blob bytes AND integrity-table entries are bit-identical between the
  zero-pack and packed paths, across member counts and page-boundary-
  straddling slabs, with and without the native runtime;
- O_DIRECT writes produce identical bytes/CRCs where the filesystem
  supports them and decline sticky-per-plugin (EINVAL -> buffered, one
  write, no lost CRC entry) where it doesn't;
- plugins without multi-buffer support get a consolidated buffer from
  the scheduler, never a BufferList.
"""

import glob
import json
import os

import numpy as np
import pytest

import torchsnapshot_tpu as ts
from torchsnapshot_tpu import _native, knobs, telemetry
from torchsnapshot_tpu.batcher import BatchedBufferStager
from torchsnapshot_tpu.event_loop import run_in_fresh_event_loop
from torchsnapshot_tpu.integrity import (
    PAGE_SIZE,
    compute_checksum_entry,
    entry_from_page_crcs,
)
from torchsnapshot_tpu.io_types import BufferList, ReadIO, WriteIO
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.telemetry import names as metric_names
from torchsnapshot_tpu.telemetry.trace import get_recorder

native_only = pytest.mark.skipif(
    _native.lib() is None, reason="native runtime unavailable on this host"
)


# ---------------------------------------------------------------------------
# native kernel units
# ---------------------------------------------------------------------------


@native_only
@pytest.mark.parametrize(
    "sizes",
    [
        [7],  # single tiny part
        [100] * 1500,  # > IOV_MAX parts: exercises the batching loop
        [3 << 20, 3 << 20, 3 << 20],  # pages straddle part boundaries
        [PAGE_SIZE, 1, PAGE_SIZE - 1],  # exact page edges
        [0, 64, 0, 64],  # zero-length parts in the stream
    ],
)
def test_pwritev_bytes_and_crcs_match_contiguous(tmp_path, sizes) -> None:
    rng = np.random.default_rng(1)
    parts = [rng.integers(0, 256, n, dtype=np.uint8).tobytes() for n in sizes]
    whole = b"".join(parts)
    p = str(tmp_path / "blob")
    pages = _native.pwritev_file_crc(p, parts, page_size=PAGE_SIZE)
    assert open(p, "rb").read() == whole
    assert entry_from_page_crcs(pages, len(whole)) == compute_checksum_entry(
        whole
    )
    # No-CRC variant writes the same bytes.
    p2 = str(tmp_path / "blob2")
    assert _native.pwritev_file_crc(p2, parts) == []
    assert open(p2, "rb").read() == whole


@native_only
def test_pwritev_empty_stream(tmp_path) -> None:
    p = str(tmp_path / "empty")
    assert _native.pwritev_file_crc(p, [], page_size=PAGE_SIZE) == []
    assert open(p, "rb").read() == b""


def test_bufferlist_checksum_entry_identity() -> None:
    rng = np.random.default_rng(2)
    parts = [
        rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        for n in (3 << 20, 1 << 20, 5 << 20, 13)
    ]
    bl = BufferList(parts)
    whole = b"".join(parts)
    assert len(bl) == len(whole)
    assert compute_checksum_entry(bl) == compute_checksum_entry(whole)
    assert bytes(bl.consolidate()) == whole


def test_addr_of_and_aligned_buffer() -> None:
    import ctypes

    # Writable buffers resolve through ctypes.from_buffer; the address
    # must equal the numpy-route answer (same memory, no copy).
    buf = bytearray(b"hello world")
    mv = memoryview(buf)
    addr = _native._addr_of(mv)
    assert addr == int(
        np.frombuffer(mv, dtype=np.uint8).ctypes.data
    )
    # Read-only views still resolve (np.frombuffer fallback).
    ro = memoryview(bytes(buf))
    assert _native._addr_of(ro) != 0
    assert _native._addr_of(memoryview(b"")) == 0
    # ctypes round-trip sanity: the address really is the first byte.
    assert ctypes.string_at(addr, 5) == b"hello"

    out = _native.aligned_buffer(12345)
    assert out.nbytes == 12345
    assert not out.readonly
    assert _native._addr_of(out) % _native.DIRECT_IO_ALIGNMENT == 0
    assert _native.is_direct_aligned(out)
    assert not _native.is_direct_aligned(out[1:])


# ---------------------------------------------------------------------------
# the slab stage: zero-pack pins
# ---------------------------------------------------------------------------


def _prepare_slab(n_members: int = 6, member_floats: int = 512):
    from torchsnapshot_tpu.batcher import batch_write_requests
    from torchsnapshot_tpu.io_preparer import prepare_write

    rng = np.random.default_rng(3)
    entries, reqs = [], []
    for i in range(n_members):
        a = rng.standard_normal(member_floats).astype(np.float32)
        entry, wr = prepare_write(a, f"t/{i}", rank=0)
        entries.append(entry)
        reqs.extend(wr)
    entries, batched = batch_write_requests(entries, reqs)
    assert len(batched) == 1
    return entries, batched[0]


def test_vectorized_slab_stage_runs_no_pack_pass(monkeypatch) -> None:
    """The acceptance pin: on the vectorized path the slab stage hands
    member buffers through untouched — no gather_memcpy, no member
    scatter, no batcher:stage_slab span; the distinct vectorized span
    is emitted instead."""
    calls = {"gather": 0, "scatter": 0}
    real_gather = _native.gather_memcpy
    monkeypatch.setattr(
        _native,
        "gather_memcpy",
        lambda *a, **k: calls.__setitem__("gather", calls["gather"] + 1)
        or real_gather(*a, **k),
    )
    real_copy = BatchedBufferStager._copy_member
    monkeypatch.setattr(
        BatchedBufferStager,
        "_copy_member",
        lambda self, *a, **k: calls.__setitem__("scatter", calls["scatter"] + 1)
        or real_copy(self, *a, **k),
    )
    with knobs.override_slab_size_threshold_bytes(1 << 20), \
            knobs.enable_write_vectorized():
        _, req = _prepare_slab()
        mark = get_recorder().mark()
        buf = run_in_fresh_event_loop(req.buffer_stager.stage_buffer())
    assert isinstance(buf, BufferList)
    assert calls == {"gather": 0, "scatter": 0}
    names = {ev.get("name") for ev in get_recorder().events_since(mark)}
    assert metric_names.SPAN_BATCHER_STAGE_SLAB_VECTORIZED in names
    assert metric_names.SPAN_BATCHER_STAGE_SLAB not in names

    # The packed path (knob off) still packs — and says so on the ring.
    with knobs.override_slab_size_threshold_bytes(1 << 20), \
            knobs.disable_write_vectorized():
        _, req = _prepare_slab()
        mark = get_recorder().mark()
        packed = run_in_fresh_event_loop(req.buffer_stager.stage_buffer())
    assert not isinstance(packed, BufferList)
    assert calls["scatter"] > 0
    names = {ev.get("name") for ev in get_recorder().events_since(mark)}
    assert metric_names.SPAN_BATCHER_STAGE_SLAB in names
    # Byte identity between the two stagings of identical member data.
    assert bytes(BufferList([packed]).consolidate()) == bytes(
        buf.consolidate()
    )


def test_vectorized_staging_cost_is_total_only() -> None:
    with knobs.override_slab_size_threshold_bytes(1 << 20):
        with knobs.enable_write_vectorized():
            _, req = _prepare_slab()
            vec_cost = req.buffer_stager.get_staging_cost_bytes()
            total = req.buffer_stager.total
        with knobs.disable_write_vectorized():
            _, req = _prepare_slab()
            packed_cost = req.buffer_stager.get_staging_cost_bytes()
    assert vec_cost == total
    assert packed_cost > vec_cost  # slab + peak member on the packed path


# ---------------------------------------------------------------------------
# end-to-end byte identity
# ---------------------------------------------------------------------------


def _take_batched(path: str, vectorized: bool, n: int, floats: int):
    rng = np.random.default_rng(11)
    arrs = {
        f"a{i}": rng.standard_normal(floats).astype(np.float32)
        for i in range(n)
    }
    ctx = (
        knobs.enable_write_vectorized()
        if vectorized
        else knobs.disable_write_vectorized()
    )
    with knobs.enable_batching(), \
            knobs.override_slab_size_threshold_bytes(32 << 20), ctx:
        ts.Snapshot.take(path, {"s": ts.PyTreeState(dict(arrs))})
    dest = ts.PyTreeState({k: np.zeros_like(v) for k, v in arrs.items()})
    ts.Snapshot(path).restore({"s": dest})
    for k, v in arrs.items():
        np.testing.assert_array_equal(dest.tree[k], v)
    [slab] = glob.glob(os.path.join(path, "batched", "*"))
    table = json.load(open(os.path.join(path, "checksums", "0")))
    [slab_entry] = [
        v for k, v in table.items() if k.startswith("batched/")
    ]
    return open(slab, "rb").read(), slab_entry


@pytest.mark.parametrize(
    "n,floats",
    [
        (8, 1000),  # small slab, many members
        (3, (2 << 20) // 4),  # 6 MiB slab: pages straddle member bounds
    ],
)
def test_vectorized_and_packed_bit_identical(tmp_path, n, floats) -> None:
    vec_bytes, vec_entry = _take_batched(
        str(tmp_path / "vec"), True, n, floats
    )
    packed_bytes, packed_entry = _take_batched(
        str(tmp_path / "packed"), False, n, floats
    )
    assert vec_bytes == packed_bytes
    assert vec_entry == packed_entry


def test_vectorized_fallback_without_native_still_zero_pack(tmp_path) -> None:
    """No native lib: the fs plugin writes BufferList parts sequentially
    into one fd (still no consolidation), the scheduler computes the
    checksum over the parts, and bytes/entries match the native path."""
    vec_bytes, vec_entry = _take_batched(str(tmp_path / "nat"), True, 5, 800)
    with knobs.disable_native():
        fb_bytes, fb_entry = _take_batched(
            str(tmp_path / "fallback"), True, 5, 800
        )
    assert fb_bytes == vec_bytes
    # Alg may differ (crc32 vs crc32c) when native is absent; sizes and
    # bytes must agree, and with zlib-crc32 both sides re-verify on read
    # (the restore inside _take_batched already did).
    assert fb_entry[2] == vec_entry[2]


def test_report_records_write_path_variant(tmp_path) -> None:
    path = str(tmp_path / "snap")
    _take_batched(path, True, 6, 1000)
    rep = telemetry.last_report("take", path=path)
    assert rep is not None and rep.write_path is not None
    if _native.lib() is not None:
        assert "vectorized" in rep.write_path
        assert rep.write_path["vectorized"] == 6 * 1000 * 4
    summary_keys = rep.to_dict()
    assert "write_path" in summary_keys


# ---------------------------------------------------------------------------
# scheduler consolidation for non-multibuffer plugins
# ---------------------------------------------------------------------------


def test_scheduler_consolidates_for_plain_plugins() -> None:
    from torchsnapshot_tpu.io_types import BufferStager, WriteReq
    from torchsnapshot_tpu.scheduler import execute_write_reqs
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    parts = [b"abc", b"defg", b"h" * 100]

    class ListStager(BufferStager):
        async def stage_buffer(self, executor=None):
            return BufferList(parts)

        def get_staging_cost_bytes(self) -> int:
            return sum(len(p) for p in parts)

    plugin = MemoryStoragePlugin(name="consolidate-test")
    assert not getattr(plugin, "supports_multibuffer")

    async def go():
        work = await execute_write_reqs(
            [WriteReq(path="x", buffer_stager=ListStager())],
            plugin,
            memory_budget_bytes=1 << 20,
            rank=0,
        )
        await work.complete()
        return work

    work = run_in_fresh_event_loop(go())
    assert plugin._blobs["x"] == b"".join(parts)
    # The consolidated write is accounted (as the plugin's own variant).
    assert work.reporter.stats.write_variant_bytes == {
        "buffered": sum(len(p) for p in parts)
    }


# ---------------------------------------------------------------------------
# O_DIRECT: serve-or-decline, sticky, no double write
# ---------------------------------------------------------------------------


@native_only
def test_direct_io_serves_or_declines_cleanly(tmp_path) -> None:
    """With the knob on, a large aligned write either goes O_DIRECT
    (variant == "direct") or the filesystem declines (EINVAL; tmpfs) —
    in BOTH cases the bytes and the integrity entry are exactly the
    buffered path's, and the decline is sticky on the plugin."""
    nbytes = 9 * (1 << 20) + 137
    buf = _native.aligned_buffer(nbytes)
    rng = np.random.default_rng(5)
    buf[:] = rng.integers(0, 256, nbytes, dtype=np.uint8).tobytes()
    plugin = FSStoragePlugin(str(tmp_path))

    async def go():
        wio = WriteIO(path="big", buf=buf)
        with knobs.enable_fs_direct_io():
            entry = await plugin.write_with_checksum(wio)
        return wio, entry

    wio, entry = run_in_fresh_event_loop(go())
    assert entry == compute_checksum_entry(bytes(buf))
    assert open(tmp_path / "big", "rb").read() == bytes(buf)
    if plugin._direct_declined:
        assert wio.variant == "fused"  # declined -> buffered fused, once
    else:
        assert wio.variant == "direct"


@native_only
def test_direct_io_decline_is_sticky_with_single_write(
    tmp_path, monkeypatch
) -> None:
    """Force the unsupported-fs outcome: the first attempt raises EINVAL,
    the plugin falls back buffered IN THE SAME CALL (exactly one file
    write, CRC entry intact) and never attempts O_DIRECT again."""
    import errno

    attempts = {"direct": 0, "fused": 0}
    real_fused = _native.write_file_crc

    def fake_direct(path, buf, page_size, do_fsync=False):
        attempts["direct"] += 1
        raise OSError(errno.EINVAL, "fs does not support O_DIRECT", path)

    def counting_fused(path, buf, page_size, do_fsync=False):
        attempts["fused"] += 1
        return real_fused(path, buf, page_size, do_fsync)

    monkeypatch.setattr(_native, "write_file_crc_direct", fake_direct)
    monkeypatch.setattr(_native, "write_file_crc", counting_fused)

    nbytes = 8 << 20
    buf = _native.aligned_buffer(nbytes)
    buf[:] = b"\x5a" * nbytes
    plugin = FSStoragePlugin(str(tmp_path))

    async def go():
        with knobs.enable_fs_direct_io():
            e1 = await plugin.write_with_checksum(WriteIO(path="a", buf=buf))
            e2 = await plugin.write_with_checksum(WriteIO(path="b", buf=buf))
        return e1, e2

    e1, e2 = run_in_fresh_event_loop(go())
    assert attempts["direct"] == 1  # sticky: second write never retries
    assert attempts["fused"] == 2  # one buffered write per blob — no double
    assert plugin._direct_declined
    assert e1 == e2 == compute_checksum_entry(bytes(buf))
    assert open(tmp_path / "a", "rb").read() == bytes(buf)
    assert open(tmp_path / "b", "rb").read() == bytes(buf)


@native_only
def test_direct_io_off_by_default(tmp_path) -> None:
    nbytes = 8 << 20
    buf = _native.aligned_buffer(nbytes)
    buf[:] = b"\x11" * nbytes
    plugin = FSStoragePlugin(str(tmp_path))
    assert not plugin._direct_eligible(buf)  # conftest pins the knob off
    with knobs.enable_fs_direct_io():
        assert plugin._direct_eligible(buf)
        assert not plugin._direct_eligible(memoryview(buf)[1:])  # unaligned
        assert not plugin._direct_eligible(b"small")  # under the floor


# ---------------------------------------------------------------------------
# fs plugin: BufferList read-back parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("disable_native", [False, True])
def test_fs_bufferlist_write_read_parity(tmp_path, disable_native) -> None:
    from torchsnapshot_tpu.knobs import _override_env
    from torchsnapshot_tpu.knobs import disable_native as disable_native_cm

    ctx = (
        disable_native_cm()
        if disable_native
        else _override_env("_TS_NOOP", None)
    )
    rng = np.random.default_rng(6)
    parts = [
        rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        for n in (4096, 1, 1 << 20)
    ]
    with ctx:
        plugin = FSStoragePlugin(str(tmp_path))

        async def go():
            await plugin.write(
                WriteIO(path="v/blob", buf=BufferList(parts))
            )
            rio = ReadIO(path="v/blob")
            await plugin.read(rio)
            await plugin.close()
            return bytes(rio.buf)

        assert run_in_fresh_event_loop(go()) == b"".join(parts)
