"""Write/read pipeline semantics: budget admission, staging-unblock point,
failure propagation.

Structural model: the reference exercises these through snapshot-level tests;
here the scheduler is tested directly with instrumented stagers/plugins.
"""

import asyncio
from typing import Dict

import pytest

from torchsnapshot_tpu.io_types import (
    BufferConsumer,
    BufferStager,
    ReadIO,
    ReadReq,
    StoragePlugin,
    WriteIO,
    WriteReq,
)
from torchsnapshot_tpu.knobs import override_per_rank_memory_budget_bytes
from torchsnapshot_tpu.scheduler import (
    get_process_memory_budget_bytes,
    sync_execute_read_reqs,
    sync_execute_write_reqs,
)
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


class TrackingStager(BufferStager):
    """Stages a fixed payload; records global concurrent staging cost."""

    live_cost = 0
    peak_cost = 0

    def __init__(self, payload: bytes):
        self.payload = payload

    async def stage_buffer(self, executor=None):
        cls = TrackingStager
        cls.live_cost += len(self.payload)
        cls.peak_cost = max(cls.peak_cost, cls.live_cost)
        await asyncio.sleep(0.001)
        cls.live_cost -= len(self.payload)
        return self.payload

    def get_staging_cost_bytes(self) -> int:
        return len(self.payload)


class CollectingConsumer(BufferConsumer):
    def __init__(self, sink: Dict[str, bytes], key: str, cost: int):
        self.sink, self.key, self.cost = sink, key, cost

    async def consume_buffer(self, buf, executor=None) -> None:
        self.sink[self.key] = bytes(buf)

    def get_consuming_cost_bytes(self) -> int:
        return self.cost


class SlowStorage(StoragePlugin):
    """Delays writes so staging finishes well before I/O."""

    def __init__(self, delay: float = 0.05):
        self.delay = delay
        self.blobs: Dict[str, bytes] = {}
        self.writes_started = 0

    async def write(self, write_io: WriteIO) -> None:
        self.writes_started += 1
        await asyncio.sleep(self.delay)
        self.blobs[write_io.path] = bytes(write_io.buf)

    async def read(self, read_io: ReadIO) -> None:
        data = self.blobs[read_io.path]
        if read_io.byte_range:
            data = data[read_io.byte_range[0] : read_io.byte_range[1]]
        read_io.buf = memoryview(data)

    async def delete(self, path: str) -> None:
        del self.blobs[path]

    async def close(self) -> None:
        pass


class FaultyStorage(SlowStorage):
    async def write(self, write_io: WriteIO) -> None:
        await asyncio.sleep(0.01)
        raise OSError("injected write failure")


def test_write_pipeline_all_written() -> None:
    loop = asyncio.new_event_loop()
    storage = SlowStorage(delay=0.0)
    reqs = [
        WriteReq(path=f"blob/{i}", buffer_stager=TrackingStager(bytes([i]) * 100))
        for i in range(50)
    ]
    pending = sync_execute_write_reqs(reqs, storage, 10**9, rank=0, event_loop=loop)
    pending.sync_complete(loop)
    loop.close()
    assert len(storage.blobs) == 50
    assert storage.blobs["blob/7"] == bytes([7]) * 100


def test_write_pipeline_respects_budget() -> None:
    TrackingStager.live_cost = 0
    TrackingStager.peak_cost = 0
    loop = asyncio.new_event_loop()
    storage = SlowStorage(delay=0.0)
    # 20 x 100B with a 300B budget: concurrent staging must stay <= 300.
    reqs = [
        WriteReq(path=f"b/{i}", buffer_stager=TrackingStager(b"x" * 100))
        for i in range(20)
    ]
    pending = sync_execute_write_reqs(reqs, storage, 300, rank=0, event_loop=loop)
    pending.sync_complete(loop)
    loop.close()
    assert TrackingStager.peak_cost <= 300
    assert len(storage.blobs) == 20


def test_oversized_request_admitted_alone() -> None:
    TrackingStager.live_cost = 0
    TrackingStager.peak_cost = 0
    loop = asyncio.new_event_loop()
    storage = SlowStorage(delay=0.0)
    reqs = [WriteReq(path="huge", buffer_stager=TrackingStager(b"x" * 1000))]
    reqs += [
        WriteReq(path=f"s/{i}", buffer_stager=TrackingStager(b"y" * 10))
        for i in range(5)
    ]
    # Budget smaller than the huge request: it must still complete (admitted
    # when the pipeline is idle) rather than deadlock.
    pending = sync_execute_write_reqs(reqs, storage, 100, rank=0, event_loop=loop)
    pending.sync_complete(loop)
    loop.close()
    assert len(storage.blobs) == 6


def test_staging_unblock_before_io_completes() -> None:
    """execute_write_reqs must return at staging-done, with writes still in
    flight (the async-take unblock point)."""
    loop = asyncio.new_event_loop()
    storage = SlowStorage(delay=0.2)
    reqs = [
        WriteReq(path=f"p/{i}", buffer_stager=TrackingStager(b"z" * 10))
        for i in range(4)
    ]
    import time

    t0 = time.monotonic()
    pending = sync_execute_write_reqs(reqs, storage, 10**9, rank=0, event_loop=loop)
    staged_at = time.monotonic() - t0
    assert len(storage.blobs) < 4  # I/O not yet drained
    pending.sync_complete(loop)
    total = time.monotonic() - t0
    loop.close()
    assert len(storage.blobs) == 4
    assert staged_at < total


def test_write_failure_propagates_via_pending_work() -> None:
    loop = asyncio.new_event_loop()
    storage = FaultyStorage()
    reqs = [WriteReq(path="x", buffer_stager=TrackingStager(b"x"))]
    pending = sync_execute_write_reqs(reqs, storage, 10**9, rank=0, event_loop=loop)
    with pytest.raises(OSError, match="injected write failure"):
        pending.sync_complete(loop)
    loop.close()


def test_staging_failure_propagates_immediately() -> None:
    class FailingStager(TrackingStager):
        async def stage_buffer(self, executor=None):
            raise ValueError("injected staging failure")

    loop = asyncio.new_event_loop()
    storage = SlowStorage(delay=0.0)
    reqs = [
        WriteReq(path="ok", buffer_stager=TrackingStager(b"ok")),
        WriteReq(path="bad", buffer_stager=FailingStager(b"bad")),
    ]
    with pytest.raises(ValueError, match="injected staging failure"):
        sync_execute_write_reqs(reqs, storage, 10**9, rank=0, event_loop=loop)
    loop.close()


def test_read_pipeline() -> None:
    loop = asyncio.new_event_loop()
    storage = MemoryStoragePlugin(name="read-pipeline-test")
    try:
        loop.run_until_complete(
            storage.write(WriteIO(path="blob", buf=b"0123456789"))
        )
        sink: Dict[str, bytes] = {}
        reqs = [
            ReadReq(path="blob", buffer_consumer=CollectingConsumer(sink, "all", 10)),
            ReadReq(
                path="blob",
                buffer_consumer=CollectingConsumer(sink, "mid", 4),
                byte_range=(3, 7),
            ),
        ]
        sync_execute_read_reqs(reqs, storage, 10**6, rank=0, event_loop=loop)
        assert sink["all"] == b"0123456789"
        assert sink["mid"] == b"3456"
    finally:
        MemoryStoragePlugin.drop_store("read-pipeline-test")
        loop.close()


def test_read_pipeline_fetched_byte_accounting() -> None:
    """classify_read attributes completed reads for the restore
    reports' read-amplification fields: without a classifier everything
    counts as fetched; a classifier returning None (cache-served reads,
    fan-out restore) keeps those bytes out of bytes_fetched while
    bytes_moved still carries them."""
    loop = asyncio.new_event_loop()
    storage = MemoryStoragePlugin(name="read-classify-test")
    try:
        for name in ("a", "b"):
            loop.run_until_complete(
                storage.write(WriteIO(path=name, buf=name.encode() * 10))
            )
        sink: Dict[str, bytes] = {}
        reqs = [
            ReadReq(path="a", buffer_consumer=CollectingConsumer(sink, "a", 10)),
            ReadReq(path="b", buffer_consumer=CollectingConsumer(sink, "b", 10)),
        ]
        out = sync_execute_read_reqs(reqs, storage, 10**6, 0, loop)
        assert out["bytes_fetched"] == 20
        assert out["bytes_moved"] == 20

        sink.clear()
        reqs = [
            ReadReq(path="a", buffer_consumer=CollectingConsumer(sink, "a", 10)),
            ReadReq(path="b", buffer_consumer=CollectingConsumer(sink, "b", 10)),
        ]
        out = sync_execute_read_reqs(
            reqs,
            storage,
            10**6,
            0,
            loop,
            classify_read=lambda r: "fetched" if r.path == "a" else None,
        )
        assert out["bytes_fetched"] == 10
        assert out["bytes_moved"] == 20
    finally:
        MemoryStoragePlugin.drop_store("read-classify-test")
        loop.close()


def test_read_pipeline_budget() -> None:
    loop = asyncio.new_event_loop()
    storage = MemoryStoragePlugin(name="read-budget-test")
    try:
        for i in range(10):
            loop.run_until_complete(
                storage.write(WriteIO(path=f"b/{i}", buf=bytes([i]) * 50))
            )
        sink: Dict[str, bytes] = {}
        reqs = [
            ReadReq(path=f"b/{i}", buffer_consumer=CollectingConsumer(sink, str(i), 50))
            for i in range(10)
        ]
        # Budget fits only 2 concurrent consumes; must still complete.
        sync_execute_read_reqs(reqs, storage, 100, rank=0, event_loop=loop)
        assert len(sink) == 10
        assert sink["3"] == bytes([3]) * 50
    finally:
        MemoryStoragePlugin.drop_store("read-budget-test")
        loop.close()


def test_memory_budget_env_override() -> None:
    with override_per_rank_memory_budget_bytes(12345):
        assert get_process_memory_budget_bytes(None) == 12345


# ---------------------------------------------------------------------------
# StagingPool + DeferredIOWork (device-snapshot async takes, round 6)
# ---------------------------------------------------------------------------


def test_staging_pool_capacity_is_slab_bounded() -> None:
    from torchsnapshot_tpu.scheduler import StagingPool

    pool = StagingPool(10**9, slab_bytes=100, slabs=2)
    assert pool.total_bytes == 200  # slabs x slab_bytes
    assert pool.memory_budget_bytes == 10**9
    assert pool.geometry() == {
        "capacity_bytes": 200,
        "slab_bytes": 100,
        "slabs": 2,
    }
    # ...but never above the process budget it is accounted against.
    clamped = StagingPool(150, slab_bytes=100, slabs=2)
    assert clamped.total_bytes == 150


def test_staging_pool_bounds_concurrent_staging() -> None:
    """20 x 100 B through a 2 x 100 B pool: concurrent staging cost must
    never exceed the pool, regardless of the (huge) process budget."""
    from torchsnapshot_tpu.scheduler import StagingPool, execute_write_reqs

    TrackingStager.live_cost = 0
    TrackingStager.peak_cost = 0
    loop = asyncio.new_event_loop()
    storage = SlowStorage(delay=0.0)
    reqs = [
        WriteReq(path=f"b/{i}", buffer_stager=TrackingStager(b"x" * 100))
        for i in range(20)
    ]
    pool = StagingPool(10**9, slab_bytes=100, slabs=2)
    pending = loop.run_until_complete(
        execute_write_reqs(
            reqs, storage, 10**9, rank=0, staging_pool=pool
        )
    )
    pending.sync_complete(loop)
    loop.close()
    assert TrackingStager.peak_cost <= 200
    assert len(storage.blobs) == 20
    assert pool.peak_reserved_bytes <= 200
    # The pool's geometry rides the pipeline telemetry into the report.
    assert pending.pipeline_telemetry()["staging_pool"]["slabs"] == 2


def test_staging_pool_oversized_request_admitted_alone() -> None:
    """Idle-admission escape hatch is inherited: one request larger
    than the whole pool serializes instead of deadlocking."""
    from torchsnapshot_tpu.scheduler import StagingPool, execute_write_reqs

    loop = asyncio.new_event_loop()
    storage = SlowStorage(delay=0.0)
    reqs = [WriteReq(path="huge", buffer_stager=TrackingStager(b"x" * 1000))]
    reqs += [
        WriteReq(path=f"s/{i}", buffer_stager=TrackingStager(b"y" * 10))
        for i in range(5)
    ]
    pool = StagingPool(10**9, slab_bytes=50, slabs=2)
    pending = loop.run_until_complete(
        execute_write_reqs(reqs, storage, 10**9, rank=0, staging_pool=pool)
    )
    pending.sync_complete(loop)
    loop.close()
    assert len(storage.blobs) == 6


def test_deferred_io_work_runs_pipeline_and_fires_on_staged() -> None:
    """Nothing stages at construction; sync_complete runs the whole
    pool-bounded pipeline, firing on_staged at the D2H boundary (before
    the write drain settles is unobservable here — assert it fired and
    the checksums table rebound to the live pipeline's)."""
    from torchsnapshot_tpu.scheduler import DeferredIOWork

    TrackingStager.live_cost = 0
    TrackingStager.peak_cost = 0
    storage = SlowStorage(delay=0.0)
    reqs = [
        WriteReq(path=f"d/{i}", buffer_stager=TrackingStager(bytes([i]) * 64))
        for i in range(12)
    ]
    work = DeferredIOWork(
        write_reqs=reqs, storage=storage, memory_budget_bytes=10**9, rank=0
    )
    assert storage.blobs == {}  # truly deferred
    staged_calls = []
    work.on_staged = lambda: staged_calls.append(len(storage.blobs))
    loop = asyncio.new_event_loop()
    work.sync_complete(loop)
    loop.close()
    assert staged_calls == [staged_calls[0]]  # fired exactly once
    assert len(storage.blobs) == 12
    assert storage.blobs["d/3"] == bytes([3]) * 64
    telemetry = work.pipeline_telemetry()
    assert telemetry["blobs"] == 12
    assert "staging" in telemetry["phases"]
