"""An in-repo fake GCS server (the fake-gcs-server role, no deps).

Implements exactly the HTTP surface storage_plugins/gcs.py exercises via
google-resumable-media, so the resumable-upload recover path and the
transient-retry taxonomy run against a REAL http server instead of mock
choreography:

- ``POST /upload/storage/v1/b/{bucket}/o?uploadType=resumable`` →
  ``Location`` session URL
- ``PUT {session}`` with ``Content-Range: bytes a-b/total`` chunks;
  ``bytes */total`` status probes (what ``ResumableUpload.recover``
  sends) answered with 308 + ``Range: bytes=0-N``
- ``GET /download/storage/v1/b/{bucket}/o/{blob}?alt=media`` with
  optional ``Range`` header → 200/206 (+ ``Content-Range``)
- ``DELETE /storage/v1/b/{bucket}/o/{blob}``

Fault injection: ``server.fail_next(n, status=503)`` makes the next
``n`` chunk PUTs (or ``where="download"``/``"initiate"`` requests) fail
with ``status`` — mid-upload brownouts, throttles, 5xx storms.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional


class _Upload:
    def __init__(self, blob: str, total: int) -> None:
        self.blob = blob
        self.total = total
        self.data = bytearray(total)
        self.received = 0  # contiguous high-water mark


class FakeGCSServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self) -> None:
        super().__init__(("127.0.0.1", 0), _Handler)
        self.blobs: Dict[str, bytes] = {}
        self.uploads: Dict[str, _Upload] = {}
        self.lock = threading.Lock()
        self._faults: Dict[str, list] = {"chunk": [], "download": [], "initiate": []}
        self.request_counts: Dict[str, int] = {
            "chunk": 0, "download": 0, "initiate": 0, "probe": 0
        }

    # -- fault injection -------------------------------------------------
    def fail_next(self, n: int, status: int = 503, where: str = "chunk") -> None:
        with self.lock:
            self._faults[where].extend([status] * n)

    def _pop_fault(self, where: str) -> Optional[int]:
        with self.lock:
            self.request_counts[where] += 1
            if self._faults[where]:
                return self._faults[where].pop(0)
        return None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> str:
        threading.Thread(target=self.serve_forever, daemon=True).start()
        return f"http://127.0.0.1:{self.server_address[1]}"

    def stop(self) -> None:
        self.shutdown()
        self.server_close()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: FakeGCSServer

    def log_message(self, *args) -> None:  # quiet
        pass

    def _reply(
        self,
        status: int,
        body: bytes = b"",
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_response(status)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    # -- resumable upload ------------------------------------------------
    def do_POST(self) -> None:
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path.startswith("/upload/storage/v1/b/"):
            body = self._read_body()  # drain BEFORE any fault reply, or the
            # leftover bytes corrupt the next keep-alive request
            fault = self.server._pop_fault("initiate")
            if fault is not None:
                self._reply(fault, b"injected fault")
                return
            meta = json.loads(body or b"{}")
            blob = meta.get("name", "")
            total = int(self.headers.get("x-upload-content-length") or 0)
            sid = uuid.uuid4().hex
            with self.server.lock:
                self.server.uploads[sid] = _Upload(blob, total)
            host = f"http://127.0.0.1:{self.server.server_address[1]}"
            self._reply(
                200, b"{}", {"Location": f"{host}/upload/session/{sid}"}
            )
            return
        self._reply(404, b"not found")

    def do_PUT(self) -> None:
        m = re.match(r"^/upload/session/([0-9a-f]+)$", self.path)
        if not m:
            self._reply(404, b"not found")
            return
        upload = self.server.uploads.get(m.group(1))
        if upload is None:
            self._reply(404, b"no such session")
            return
        body = self._read_body()
        crange = self.headers.get("Content-Range", "")
        probe = re.match(r"^bytes \*/(\d+|\*)$", crange)
        if probe:
            with self.server.lock:
                self.server.request_counts["probe"] += 1
            self._incomplete(upload)
            return
        dataspec = re.match(r"^bytes (\d+)-(\d+)/(\d+)$", crange)
        if not dataspec:
            self._reply(400, f"bad Content-Range {crange!r}".encode())
            return
        fault = self.server._pop_fault("chunk")
        if fault is not None:
            self._reply(fault, b"injected fault")
            return
        start, end, total = (int(g) for g in dataspec.groups())
        if len(body) != end - start + 1:
            self._reply(400, b"length mismatch")
            return
        with self.server.lock:
            upload.total = total
            if len(upload.data) < total:
                upload.data.extend(bytearray(total - len(upload.data)))
            upload.data[start : end + 1] = body
            if start <= upload.received:
                upload.received = max(upload.received, end + 1)
        if upload.received >= total:
            with self.server.lock:
                self.server.blobs[upload.blob] = bytes(upload.data[:total])
            self._reply(
                200,
                json.dumps(
                    {"name": upload.blob, "size": str(total)}
                ).encode(),
                {"Content-Type": "application/json"},
            )
        else:
            self._incomplete(upload)

    def _incomplete(self, upload: _Upload) -> None:
        headers = {}
        if upload.received > 0:
            headers["Range"] = f"bytes=0-{upload.received - 1}"
        self._reply(308, b"", headers)

    # -- download --------------------------------------------------------
    def do_GET(self) -> None:
        parsed = urllib.parse.urlparse(self.path)
        m = re.match(r"^/download/storage/v1/b/[^/]+/o/(.+)$", parsed.path)
        if not m:
            self._reply(404, b"not found")
            return
        fault = self.server._pop_fault("download")
        if fault is not None:
            self._reply(fault, b"injected fault")
            return
        blob = urllib.parse.unquote(m.group(1))
        data = self.server.blobs.get(blob)
        if data is None:
            self._reply(404, b"no such object")
            return
        rng = self.headers.get("Range")
        if rng:
            rm = re.match(r"^bytes=(\d+)-(\d+)$", rng)
            if rm is None:
                # Open-ended/suffix ranges aren't needed by ChunkedDownload;
                # answer 400 cleanly instead of crashing the handler (which
                # would surface as a retriable connection error and hang
                # the collective-progress retry until its deadline).
                self._reply(400, f"unsupported Range {rng!r}".encode())
                return
            start, end = int(rm.group(1)), min(int(rm.group(2)), len(data) - 1)
            body = data[start : end + 1]
            self._reply(
                206,
                body,
                {
                    "Content-Range": f"bytes {start}-{end}/{len(data)}",
                    "Content-Type": "application/octet-stream",
                },
            )
        else:
            self._reply(
                200, data, {"Content-Type": "application/octet-stream"}
            )

    # -- delete ----------------------------------------------------------
    def do_DELETE(self) -> None:
        m = re.match(r"^/storage/v1/b/[^/]+/o/(.+)$", self.path)
        if not m:
            self._reply(404, b"not found")
            return
        blob = urllib.parse.unquote(m.group(1))
        with self.server.lock:
            existed = self.server.blobs.pop(blob, None) is not None
        self._reply(204 if existed else 404)
