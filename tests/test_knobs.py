"""Knob getters, env sourcing, and override context managers.

Reference parity: torchsnapshot/knobs.py:32-98 (same knob surface under the
TORCHSNAPSHOT_TPU_ prefix).
"""

from __future__ import annotations

import os

from torchsnapshot_tpu import knobs


def test_defaults() -> None:
    assert knobs.get_max_chunk_size_bytes() == 512 * 1024 * 1024
    assert knobs.get_max_shard_size_bytes() == 512 * 1024 * 1024
    assert knobs.get_slab_size_threshold_bytes() == 128 * 1024 * 1024
    assert not knobs.is_batching_enabled()
    assert knobs.get_per_rank_memory_budget_bytes_override() is None
    assert not knobs.is_partitioner_disabled()
    assert knobs.get_per_rank_io_concurrency() == 16
    assert knobs.get_staging_threads() == 4


def test_override_context_managers_restore_prior_value() -> None:
    with knobs.override_max_chunk_size_bytes(1234):
        assert knobs.get_max_chunk_size_bytes() == 1234
        with knobs.override_max_chunk_size_bytes(99):
            assert knobs.get_max_chunk_size_bytes() == 99
        assert knobs.get_max_chunk_size_bytes() == 1234
    assert knobs.get_max_chunk_size_bytes() == 512 * 1024 * 1024

    with knobs.override_max_shard_size_bytes(77):
        assert knobs.get_max_shard_size_bytes() == 77
    with knobs.override_slab_size_threshold_bytes(55):
        assert knobs.get_slab_size_threshold_bytes() == 55
    with knobs.override_per_rank_memory_budget_bytes(4096):
        assert knobs.get_per_rank_memory_budget_bytes_override() == 4096
    assert knobs.get_per_rank_memory_budget_bytes_override() is None


def test_batching_enabled_by_env_presence() -> None:
    """Presence of the env var — any value — turns batching on
    (reference knobs.py:53-57)."""
    assert not knobs.is_batching_enabled()
    with knobs.enable_batching():
        assert knobs.is_batching_enabled()
    assert not knobs.is_batching_enabled()
    os.environ["TORCHSNAPSHOT_TPU_ENABLE_BATCHING"] = "0"
    try:
        assert knobs.is_batching_enabled()
    finally:
        del os.environ["TORCHSNAPSHOT_TPU_ENABLE_BATCHING"]


def test_env_values_read_lazily() -> None:
    os.environ["TORCHSNAPSHOT_TPU_PER_RANK_IO_CONCURRENCY"] = "3"
    os.environ["TORCHSNAPSHOT_TPU_STAGING_THREADS"] = "2"
    try:
        assert knobs.get_per_rank_io_concurrency() == 3
        assert knobs.get_staging_threads() == 2
    finally:
        del os.environ["TORCHSNAPSHOT_TPU_PER_RANK_IO_CONCURRENCY"]
        del os.environ["TORCHSNAPSHOT_TPU_STAGING_THREADS"]
    assert knobs.get_per_rank_io_concurrency() == 16
    assert knobs.get_staging_threads() == 4


def test_native_disable_knob() -> None:
    """The native-runtime kill-switch moved onto the knob surface
    (snaplint knob-env-literal: no TORCHSNAPSHOT_TPU_* env reads
    outside knobs.py); _native.lib() honors it before touching its
    load cache."""
    from torchsnapshot_tpu import _native

    assert not knobs.is_native_disabled()
    with knobs.disable_native():
        assert knobs.is_native_disabled()
        assert _native.lib() is None
    assert not knobs.is_native_disabled()


def test_wait_durable_timeout_knob() -> None:
    assert knobs.get_wait_durable_timeout_seconds() == 1800.0
    with knobs.override_wait_durable_timeout_seconds(0.25):
        assert knobs.get_wait_durable_timeout_seconds() == 0.25
    assert knobs.get_wait_durable_timeout_seconds() == 1800.0


def test_progress_knobs() -> None:
    """Heartbeat interval (conftest zeroes it for the suite; the
    out-of-suite default is 1 s), progress dir, and the <= 0 disable
    contract progress.progress_path_for keys off."""
    assert knobs.get_progress_interval_seconds() == 0.0  # conftest
    with knobs.override_progress_interval_seconds(0.5):
        assert knobs.get_progress_interval_seconds() == 0.5
    assert knobs.get_progress_interval_seconds() == 0.0
    # The packaged default (no env var at all) is 1 s.
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_PROGRESS_SECONDS", None)
    try:
        assert knobs.get_progress_interval_seconds() == 1.0
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_PROGRESS_SECONDS"] = prev
    assert knobs.get_progress_dir() is None
    with knobs.override_progress_dir("/tmp/progress-out"):
        assert knobs.get_progress_dir() == "/tmp/progress-out"
    assert knobs.get_progress_dir() is None


def test_async_device_snapshot_knob() -> None:
    """Device-snapshot deferral is the DEFAULT async story; only an
    explicit "0" opts back into staging-before-return."""
    assert knobs.is_async_device_snapshot_enabled()
    with knobs.disable_async_device_snapshot():
        assert not knobs.is_async_device_snapshot_enabled()
    assert knobs.is_async_device_snapshot_enabled()
    os.environ["TORCHSNAPSHOT_TPU_ASYNC_DEVICE_SNAPSHOT"] = "1"
    try:
        assert knobs.is_async_device_snapshot_enabled()
    finally:
        del os.environ["TORCHSNAPSHOT_TPU_ASYNC_DEVICE_SNAPSHOT"]


def test_staging_pool_knobs() -> None:
    assert knobs.get_staging_pool_slab_bytes() == 128 * 1024 * 1024
    assert knobs.get_staging_pool_slabs() == 2
    with knobs.override_staging_pool_slab_bytes(4096):
        assert knobs.get_staging_pool_slab_bytes() == 4096
    with knobs.override_staging_pool_slabs(3):
        assert knobs.get_staging_pool_slabs() == 3
    assert knobs.get_staging_pool_slab_bytes() == 128 * 1024 * 1024
    assert knobs.get_staging_pool_slabs() == 2


def test_async_visible_budget_knob() -> None:
    assert knobs.get_async_visible_budget_seconds() == 5.0
    with knobs.override_async_visible_budget_seconds(0.25):
        assert knobs.get_async_visible_budget_seconds() == 0.25
    with knobs.override_async_visible_budget_seconds(0):
        # <= 0 disables the doctor rule; the getter reports it raw.
        assert knobs.get_async_visible_budget_seconds() == 0.0
    assert knobs.get_async_visible_budget_seconds() == 5.0


def test_autotune_kill_switch_knob() -> None:
    """Suite default (conftest) is "0" = off; the packaged default (no
    env var) is ON — recurring saves are the tuner's training signal."""
    assert not knobs.is_autotune_enabled()  # conftest kill switch
    with knobs.enable_autotune():
        assert knobs.is_autotune_enabled()
    assert not knobs.is_autotune_enabled()
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_AUTOTUNE", None)
    try:
        assert knobs.is_autotune_enabled()
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_AUTOTUNE"] = prev


def test_fanout_restore_knob() -> None:
    """Suite default (conftest) is "0" = every-rank-reads; the packaged
    default (no env var) is ON — single-reader fan-out is the
    "millions of users" read-path story, and rank 0's reading is
    broadcast-agreed at restore start so skew can't strand a
    rendezvous."""
    assert not knobs.is_fanout_restore_enabled()  # conftest pin
    with knobs.enable_fanout_restore():
        assert knobs.is_fanout_restore_enabled()
    assert not knobs.is_fanout_restore_enabled()
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_FANOUT_RESTORE", None)
    try:
        assert knobs.is_fanout_restore_enabled()
        with knobs.disable_fanout_restore():
            assert not knobs.is_fanout_restore_enabled()
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_FANOUT_RESTORE"] = prev


def test_peer_tier_knobs() -> None:
    """Suite default (conftest) pins the peer tier off; the packaged
    default (no env var) is ON — but inert until a multi-rank pg with a
    store configures the replicator, so single-process jobs never start
    a server. Ring offset, cache budget and transfer timeout resolve
    env > default."""
    assert not knobs.is_peer_tier_enabled()  # conftest pin
    with knobs.enable_peer_tier():
        assert knobs.is_peer_tier_enabled()
    assert not knobs.is_peer_tier_enabled()
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_PEER_TIER", None)
    try:
        assert knobs.is_peer_tier_enabled()
        with knobs.disable_peer_tier():
            assert not knobs.is_peer_tier_enabled()
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_PEER_TIER"] = prev

    assert knobs.get_peer_ring_offset() == 1
    with knobs.override_peer_ring_offset(3):
        assert knobs.get_peer_ring_offset() == 3
    assert knobs.get_peer_ring_offset() == 1

    assert knobs.get_peer_cache_budget_bytes() == 1024 * 1024 * 1024
    with knobs.override_peer_cache_budget_bytes(1234):
        assert knobs.get_peer_cache_budget_bytes() == 1234
    assert knobs.get_peer_cache_budget_bytes() == 1024 * 1024 * 1024

    assert knobs.get_peer_transfer_timeout_seconds() == 30.0
    with knobs.override_peer_transfer_timeout_seconds(2.5):
        assert knobs.get_peer_transfer_timeout_seconds() == 2.5
    assert knobs.get_peer_transfer_timeout_seconds() == 30.0


def test_write_path_knobs() -> None:
    """Zero-pack vectorized writes default ON (an explicit "0" restores
    the packed slab path); O_DIRECT defaults OFF and is pinned off by
    the suite conftest (CI filesystems vary). Both are tunables: the
    autotuner can flip them through the override layer, env wins."""
    assert knobs.is_write_vectorized_enabled()
    with knobs.disable_write_vectorized():
        assert not knobs.is_write_vectorized_enabled()
    with knobs.enable_write_vectorized():
        assert knobs.is_write_vectorized_enabled()
    assert knobs.is_write_vectorized_enabled()

    assert not knobs.is_fs_direct_io_enabled()  # conftest pin (and default)
    with knobs.enable_fs_direct_io():
        assert knobs.is_fs_direct_io_enabled()
    assert not knobs.is_fs_direct_io_enabled()
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_FS_DIRECT_IO", None)
    try:
        assert not knobs.is_fs_direct_io_enabled()  # packaged default OFF
        # Tuner override applies when no env var is set; env wins over it.
        knobs.set_tuner_override("TORCHSNAPSHOT_TPU_FS_DIRECT_IO", 1)
        assert knobs.is_fs_direct_io_enabled()
        with knobs.disable_fs_direct_io():
            assert not knobs.is_fs_direct_io_enabled()
    finally:
        knobs.clear_tuner_overrides()
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_FS_DIRECT_IO"] = prev

    snap = knobs.tunable_snapshot()
    assert snap["write_vectorized"] == 1
    assert snap["fs_direct_io"] == 0


def test_memory_budget_fraction_knob() -> None:
    assert knobs.get_memory_budget_fraction() == 0.6
    with knobs.override_memory_budget_fraction(0.3):
        assert knobs.get_memory_budget_fraction() == 0.3
    assert knobs.get_memory_budget_fraction() == 0.6


def test_tuner_override_layer_precedence() -> None:
    """The chain every tunable getter resolves: env var (operator) >
    programmatic tuner override > documented default."""
    assert knobs.get_staging_threads() == 4
    knobs.set_tuner_override("TORCHSNAPSHOT_TPU_STAGING_THREADS", 9)
    try:
        assert knobs.get_staging_threads() == 9
        # Env always wins over an installed override.
        with knobs.override_staging_threads(2):
            assert knobs.get_staging_threads() == 2
        assert knobs.get_staging_threads() == 9
    finally:
        knobs.clear_tuner_overrides()
    assert knobs.get_staging_threads() == 4
    assert knobs.get_tuner_overrides() == {}


def test_tunable_snapshot_reports_effective_values() -> None:
    snap = knobs.tunable_snapshot()
    assert snap["staging_threads"] == 4
    assert snap["io_concurrency"] == 16
    assert snap["memory_budget_fraction"] == 0.6
    knobs.set_tuner_override("TORCHSNAPSHOT_TPU_PER_RANK_IO_CONCURRENCY", 32)
    try:
        assert knobs.tunable_snapshot()["io_concurrency"] == 32
        with knobs.override_per_rank_io_concurrency(8):
            assert knobs.tunable_snapshot()["io_concurrency"] == 8
    finally:
        knobs.clear_tuner_overrides()
    assert knobs.tunable_snapshot()["io_concurrency"] == 16


def test_ledger_knobs() -> None:
    """Suite default (conftest) is "0" = off; the packaged default (no
    env var) is ON — the run ledger is the always-on goodput substrate.
    A non-positive max-records bound also disables recording."""
    assert not knobs.is_ledger_enabled()  # conftest pin
    with knobs.enable_ledger():
        assert knobs.is_ledger_enabled()
        with knobs.override_ledger_max_records(0):
            assert not knobs.is_ledger_enabled()
        with knobs.override_ledger_max_records(7):
            assert knobs.get_ledger_max_records() == 7
    assert not knobs.is_ledger_enabled()
    assert knobs.get_ledger_max_records() == 4096
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_LEDGER", None)
    try:
        assert knobs.is_ledger_enabled()
        with knobs.disable_ledger():
            assert not knobs.is_ledger_enabled()
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_LEDGER"] = prev


def test_cdn_knobs() -> None:
    """Suite default (conftest) AND packaged default are off: the CDN
    publish hook must be an explicit opt-in on the training side. The
    pull timeout inherits the peer transfer timeout unless pinned."""
    assert not knobs.is_cdn_enabled()  # conftest pin
    with knobs.enable_cdn():
        assert knobs.is_cdn_enabled()
    assert not knobs.is_cdn_enabled()
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_CDN", None)
    try:
        assert not knobs.is_cdn_enabled()  # packaged default: off
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_CDN"] = prev

    assert knobs.get_cdn_staleness_budget_seconds() == 5.0
    os.environ["TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS"] = "0.5"
    try:
        assert knobs.get_cdn_staleness_budget_seconds() == 0.5
    finally:
        del os.environ["TORCHSNAPSHOT_TPU_CDN_STALENESS_BUDGET_SECONDS"]

    assert (
        knobs.get_cdn_pull_timeout_seconds()
        == knobs.get_peer_transfer_timeout_seconds()
    )
    os.environ["TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS"] = "2.5"
    try:
        assert knobs.get_cdn_pull_timeout_seconds() == 2.5
    finally:
        del os.environ["TORCHSNAPSHOT_TPU_CDN_PULL_TIMEOUT_SECONDS"]


def test_fleet_obs_knob() -> None:
    """Suite default (conftest) AND packaged default are off: the
    __obs/ metrics plane must be an explicit opt-in — no publish
    traffic rides the coordination store unless asked for."""
    assert not knobs.is_fleet_obs_enabled()  # conftest pin
    with knobs.enable_fleet_obs():
        assert knobs.is_fleet_obs_enabled()
    assert not knobs.is_fleet_obs_enabled()
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_FLEET_OBS", None)
    try:
        assert not knobs.is_fleet_obs_enabled()  # packaged default: off
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_FLEET_OBS"] = prev


def test_history_max_records_knob() -> None:
    assert knobs.get_history_max_records() == 0  # conftest zeroes it
    with knobs.override_history_max_records(7):
        assert knobs.get_history_max_records() == 7
    assert knobs.get_history_max_records() == 0
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_HISTORY_MAX_RECORDS", None)
    try:
        assert knobs.get_history_max_records() == 512
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_HISTORY_MAX_RECORDS"] = prev


def test_coordination_topology_knobs() -> None:
    # Tree barrier: default ON; "0" is the LinearBarrier kill switch.
    assert knobs.is_tree_barrier_enabled()
    with knobs.disable_tree_barrier():
        assert not knobs.is_tree_barrier_enabled()
    assert knobs.is_tree_barrier_enabled()
    with knobs.enable_tree_barrier():
        assert knobs.is_tree_barrier_enabled()
    # Fanout: default 16, floor of 2 (a 1-ary "tree" is a chain).
    assert knobs.get_barrier_fanout() == 16
    with knobs.override_barrier_fanout(4):
        assert knobs.get_barrier_fanout() == 4
    with knobs.override_barrier_fanout(1):
        assert knobs.get_barrier_fanout() == 2
    assert knobs.get_barrier_fanout() == 16
    # Store shards: conftest pins the suite to the single-hub default.
    assert knobs.get_store_shards() == 1
    with knobs.override_store_shards(4):
        assert knobs.get_store_shards() == 4
    assert knobs.get_store_shards() == 1


def test_coordination_knobs_are_tunables() -> None:
    """barrier_fanout / store_shards ride the tuner override layer
    (env always wins) and appear in every report's tunables snapshot."""
    snap = knobs.tunable_snapshot()
    assert snap["barrier_fanout"] == 16
    assert snap["store_shards"] == 1
    try:
        knobs.set_tuner_override(knobs._BARRIER_FANOUT_ENV, 8)
        assert knobs.get_barrier_fanout() == 8
        with knobs.override_barrier_fanout(32):
            assert knobs.get_barrier_fanout() == 32  # env wins
    finally:
        knobs.clear_tuner_override(knobs._BARRIER_FANOUT_ENV)
    assert knobs.get_barrier_fanout() == 16


def test_slo_knobs() -> None:
    """Suite default (conftest) is "0" = off; the packaged default (no
    env var) is ON — the SLO evaluation rides every committed step
    unless explicitly killed. Window/threshold/budget knobs carry the
    multi-window burn-rate geometry."""
    assert not knobs.is_slo_enabled()  # conftest pin
    with knobs.enable_slo():
        assert knobs.is_slo_enabled()
    assert not knobs.is_slo_enabled()
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_SLO", None)
    try:
        assert knobs.is_slo_enabled()  # packaged default: on
        with knobs.disable_slo():
            assert not knobs.is_slo_enabled()
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_SLO"] = prev

    assert knobs.get_slo_fast_window() == 8
    assert knobs.get_slo_slow_window() == 64
    with knobs.override_slo_windows(3, 12):
        assert knobs.get_slo_fast_window() == 3
        assert knobs.get_slo_slow_window() == 12
    assert knobs.get_slo_fast_window() == 8
    assert knobs.get_slo_fast_burn_threshold() == 2.0
    assert knobs.get_slo_slow_burn_threshold() == 1.0
    assert knobs.get_slo_error_budget_fraction() == 0.1

    # Per-objective targets; each override context restores the prior
    # geometry, and a <= 0 target disables the objective (asserted in
    # test_slo.py).
    assert knobs.get_slo_restore_seconds() == 60.0
    with knobs.override_slo_restore_seconds(0.5):
        assert knobs.get_slo_restore_seconds() == 0.5
    assert knobs.get_slo_restore_seconds() == 60.0
    assert knobs.get_slo_mirror_lag_seconds() == 120.0
    with knobs.override_slo_mirror_lag_seconds(2.0):
        assert knobs.get_slo_mirror_lag_seconds() == 2.0
    assert knobs.get_slo_overhead_fraction() == 0.1
    with knobs.override_slo_overhead_fraction(0.5):
        assert knobs.get_slo_overhead_fraction() == 0.5
    assert knobs.get_slo_coordination_fraction() == 0.3
    with knobs.override_slo_coordination_fraction(0.9):
        assert knobs.get_slo_coordination_fraction() == 0.9


def test_bundle_knobs(tmp_path) -> None:
    """Suite default (conftest) zeroes the size cap = capture disabled;
    the packaged default is a 64 MiB cap with a 5-minute per-dir rate
    limit. The bundle dir defaults to <root>/.bundles (getter: None)."""
    assert knobs.get_bundle_max_bytes() == 0  # conftest pin
    with knobs.override_bundle_max_bytes(1024):
        assert knobs.get_bundle_max_bytes() == 1024
    assert knobs.get_bundle_max_bytes() == 0
    prev = os.environ.pop("TORCHSNAPSHOT_TPU_BUNDLE_MAX_BYTES", None)
    try:
        assert knobs.get_bundle_max_bytes() == 64 * 1024 * 1024
    finally:
        if prev is not None:
            os.environ["TORCHSNAPSHOT_TPU_BUNDLE_MAX_BYTES"] = prev

    assert knobs.get_bundle_dir() is None
    with knobs.override_bundle_dir(str(tmp_path)):
        assert knobs.get_bundle_dir() == str(tmp_path)
    assert knobs.get_bundle_dir() is None

    assert knobs.get_bundle_min_interval_seconds() == 300.0
    with knobs.override_bundle_min_interval_seconds(0.0):
        assert knobs.get_bundle_min_interval_seconds() == 0.0
    assert knobs.get_bundle_min_interval_seconds() == 300.0


def test_cold_start_budget_fraction_knob() -> None:
    assert knobs.get_cold_start_budget_fraction() == 0.5
    with knobs.override_cold_start_budget_fraction(0.1):
        assert knobs.get_cold_start_budget_fraction() == 0.1
    with knobs.override_cold_start_budget_fraction(0):
        assert knobs.get_cold_start_budget_fraction() == 0  # rule off
    assert knobs.get_cold_start_budget_fraction() == 0.5
